"""ResNet in flax — the benchmark model family of the reference's
``examples/pytorch_benchmark.py`` / ``pytorch_cifar10_resnet.py`` [U]
(SURVEY.md §2.2, §6: ResNet-50/ImageNet is the north-star config).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU), bfloat16
compute with float32 parameters/statistics (MXU-friendly), BatchNorm with
*local* batch statistics per rank — exactly the semantics data-parallel
training has on the reference (each worker normalizes its own shard).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides)(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides)(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


def space_to_depth(x, factor: int = 2):
    """NHWC space-to-depth: ``[B, H, W, C] -> [B, H/f, W/f, f*f*C]``."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // factor, factor, w // factor, factor, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // factor, w // factor, factor * factor * c)


class ResNet(nn.Module):
    """Configurable ResNet; stage_sizes [3,4,6,3] + bottleneck = ResNet-50.

    ``stem="space_to_depth"`` replaces the 7x7/s2 input convolution with a
    2x2 space-to-depth rearrangement followed by a 4x4/s1 convolution on the
    12-channel result — mathematically an 8x8/s2 convolution (a superset of
    the 7x7), the standard TPU formulation (MLPerf ResNet): a 3-channel
    conv wastes the 128-wide MXU contraction, the s2d form feeds it 12
    channels and runs ~4x faster with equivalent accuracy.  Default stays
    the canonical "conv" stem.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_images: bool = False  # CIFAR-style stem (3x3, no initial pool)
    stem: str = "conv"  # "conv" (canonical 7x7/s2) | "space_to_depth"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        elif self.stem == "space_to_depth":
            # pad (1,2)x(1,2) ≙ the 8x8/s2 SAME geometry on the full image
            x = space_to_depth(x, 2)
            x = conv(
                self.num_filters, (4, 4), padding=((1, 2), (1, 2)),
                name="conv_init",
            )(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
