"""LeNet-5 for MNIST — the model of the reference's flagship example
``examples/pytorch_mnist.py`` [U] (the driver's tracked config #1,
BASELINE.md), in flax."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    """Classic LeNet-5: two conv+pool stages, three dense layers."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: [batch, 28, 28, 1]
        x = nn.Conv(6, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)
