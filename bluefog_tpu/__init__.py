"""bluefog_tpu — TPU-native decentralized (gossip) training framework.

A ground-up JAX/XLA rebuild of the capabilities of Bluefog
(arXiv:2111.04287; upstream layout ``bluefog/`` [U], see SURVEY.md):
virtual-topology gossip collectives (``neighbor_allreduce``,
``hierarchical_neighbor_allreduce``), one-sided window ops emulated with
device-memory mailboxes, and decentralized optimizers — all lowered to XLA
collectives (``lax.ppermute`` / ``psum`` / ``all_to_all``) over a
``jax.sharding.Mesh``, with no MPI/NCCL/GPU anywhere.

The public surface mirrors ``bluefog.torch`` (reference
``bluefog/torch/mpi_ops.py``, ``bluefog/common/basics.py`` [U]) but is
idiomatic JAX: every collective is a pure function, usable both eagerly on
per-rank ("rank-major") arrays and inside user ``jit``/``shard_map`` code.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under jax.experimental with the same
    # core keyword signature (mesh/in_specs/out_specs); alias it so the
    # package (and its tests) run on either generation.  The newer
    # partial-manual spelling ``axis_names={manual axes}`` maps to the
    # older complement ``auto={the other mesh axes}``.
    from jax.experimental import shard_map as _shard_map_mod

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, **kw):
        axis_names = kw.pop("axis_names", None)
        if axis_names is not None and "auto" not in kw:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        if "check_vma" in kw and "check_rep" not in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_mod.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

    _jax.shard_map = _shard_map_compat

try:
    # jax 0.4.x shard_map has no replication rule for the remat name
    # primitive, so ``checkpoint_name`` inside a shard_map'd function dies
    # with "No replication rule for name".  name_p is identity-shaped —
    # the standard check/rewrite rules are exactly right for it; newer jax
    # registers them itself (and this block no-ops on ImportError there).
    from jax._src.ad_checkpoint import name_p as _name_p
    from jax.experimental import shard_map as _sm_mod

    if _name_p not in getattr(_sm_mod, "_check_rules", {}):
        _sm_mod.register_standard_check(_name_p)
        _sm_mod.register_standard_rewrite(_name_p)
    del _name_p, _sm_mod
except (ImportError, AttributeError):  # pragma: no cover - other jax gens
    pass

from bluefog_tpu.version import __version__

from bluefog_tpu.core.basics import (
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    machine_size,
    machine_rank,
    mesh,
    set_topology,
    load_topology,
    set_machine_topology,
    load_machine_topology,
    in_neighbor_ranks,
    out_neighbor_ranks,
    in_neighbor_machine_ranks,
    out_neighbor_machine_ranks,
    is_topo_weighted,
    is_machine_topo_weighted,
    unified_mpi_window_model_supported,
)

from bluefog_tpu.ops import (
    Handle,
    device_sync,
    allreduce,
    allreduce_nonblocking,
    allgather,
    allgather_nonblocking,
    broadcast,
    broadcast_nonblocking,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    barrier,
    poll,
    synchronize,
    wait,
)

from bluefog_tpu.windows import (
    win_create,
    win_free,
    win_put,
    win_put_nonblocking,
    win_get,
    win_get_nonblocking,
    win_accumulate,
    win_accumulate_nonblocking,
    win_update,
    win_put_update,
    win_update_then_collect,
    win_wait,
    win_poll,
    win_mutex,
    get_win_version,
    win_associated_p,
    win_set_exposed,
    turn_on_win_ops_with_associated_p,
    turn_off_win_ops_with_associated_p,
)

from bluefog_tpu.optim import (
    CommunicationType,
    DistributedAdaptThenCombineOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedGradientAllreduceOptimizer,
    DistributedWinPutOptimizer,
    one_peer_plan_schedule,
    broadcast_parameters,
    broadcast_optimizer_state,
)

from bluefog_tpu.algorithms import (
    DistributedEXTRAOptimizer,
    DistributedGradientTrackingOptimizer,
    DistributedPushDIGingOptimizer,
)

from bluefog_tpu.timeline import (
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
)

from bluefog_tpu import topology_util

__all__ = [k for k in dict(vars()) if not k.startswith("_")]
